"""graftlint engine: one parse per file, rule visitors multiplexed over one
walk, then whole-program rules over the folded project index.

The invariants this codebase learned the hard way (GC-killed fire-and-forget
asyncio tasks, blocking calls on the event-loop thread, pickle of
unauthenticated wire bytes, silent bounded-buffer trims) keep re-appearing as
review comments. This package machine-checks them: each rule is an AST
visitor; the engine parses each file ONCE and drives every applicable rule
over a single depth-first walk (lexical order, parent links and scope stacks
maintained by the engine so rules stay small).

Two phases since the whole-program extension:

- **Phase 1** (per file, cacheable): rule visitors produce raw findings with
  line spans, the suppression scanner produces candidates, and the
  IndexCollector rides the same walk to produce the file's project-index
  contribution. The whole product is a plain dict — the parse cache
  (cache.py) serves it for unchanged files without reparsing.
- **Phase 2** (whole program, always live): contributions fold into a
  ProjectIndex and the cross-file rules (rules_xfile.py) check the
  cross-process contracts — RPC verbs, adopted config, ctx propagation,
  the metric surface, dtype-kind.

Suppressions apply centrally AFTER phase 2, so a cross-file finding is
silenced by the same inline mechanism as a per-file one.

Suppression: ``# graftlint: disable=<rule>[,<rule>...]  <reason>`` on the
finding's line. The reason is REQUIRED — a disable comment without one does
not suppress and is itself reported (rule id ``bad-suppression``). Reasons
are carried into the JSON report so the suppression inventory is diffable
across PRs.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize as _tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional

# Matches the inline disable directive (syntax in the module docstring —
# spelling it here would make this comment parse as a directive itself).
# The rule list tolerates spaces around commas ("rule-a, rule-b").
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable="
    r"([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)[ \t]*[-—:]*[ \t]*(.*?)\s*$"
)

BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"


@dataclass(frozen=True)
class Finding:
    """One rule violation: file:line, rule id, one-line explanation."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Suppression:
    path: str
    line: int
    rules: tuple
    reason: str


class Rule:
    """Base class for graftlint per-file rules.

    Subclasses set ``id`` and ``explanation`` and override any of the hook
    methods. ``visit`` runs on every node in document order (parents before
    children); ``leave`` runs after a node's subtree completes. Rules report
    via ``ctx.report(node_or_line, message)``.
    """

    id: str = ""
    explanation: str = ""

    def applies_to(self, path: str) -> bool:
        return True

    def begin_file(self, ctx: "FileContext") -> None:
        pass

    def visit(self, node: ast.AST, ctx: "FileContext") -> None:
        pass

    def leave(self, node: ast.AST, ctx: "FileContext") -> None:
        pass

    def end_file(self, ctx: "FileContext") -> None:
        pass


class FileContext:
    """Per-file state the engine maintains for every rule: source path and
    lines, parent links, and the enclosing function/class stacks."""

    def __init__(self, path: str, tree: ast.Module, lines: list):
        self.path = path
        self.tree = tree
        self.lines = lines  # 0-indexed source lines (for suppression lookup)
        self.parents: dict = {}
        # Innermost-last stacks. func_stack holds FunctionDef/AsyncFunctionDef
        # nodes; class_stack holds ClassDef nodes.
        self.func_stack: list = []
        self.class_stack: list = []
        self._raw_findings: dict = {}  # rule_id -> [ (line, end, message) ]
        self.stats: dict = {}  # rule_id -> arbitrary JSON-able stats
        self.index: dict = {}  # this file's project-index contribution

    # -- helpers rules lean on ------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def in_async_context(self) -> bool:
        """True when the innermost enclosing function is ``async def`` — a
        nested plain ``def`` (executor thunk, callback) exits the async
        context even though an async function encloses it lexically."""
        return bool(self.func_stack) and isinstance(
            self.func_stack[-1], ast.AsyncFunctionDef
        )

    def outermost_function(self) -> Optional[ast.AST]:
        return self.func_stack[0] if self.func_stack else None

    def report(self, rule: Rule, node, message: str = "") -> None:
        """``node`` may be an AST node, a bare line int, or a
        ``(line, end_line)`` span. The whole extent matters: a disable
        comment belongs on the line a formatter puts it — often the CLOSING
        line of a multi-line statement — and must still match, so rules
        that buffer findings keep spans, not bare ints."""
        if isinstance(node, int):
            line = end = node
        elif isinstance(node, tuple):
            line, end = node
        else:
            line = getattr(node, "lineno", 0)
            end = getattr(node, "end_lineno", None) or line
        self._raw_findings.setdefault(rule.id, []).append(
            (line, end, message or rule.explanation)
        )


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def parse_suppressions(path: str, source: str) -> list:
    """Disable directives from actual COMMENT tokens only — a
    "# graftlint: disable=..." spelled inside a string literal (test
    fixtures, docs) is data, not a directive."""
    out = []
    if "graftlint" not in source:
        return out
    try:
        tokens = list(_tokenize.generate_tokens(io.StringIO(source).readline))
    except (_tokenize.TokenError, IndentationError, SyntaxError):
        return out  # un-tokenizable source already surfaced as a parse error
    for tok in tokens:
        if tok.type != _tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if m is None:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        out.append(Suppression(path, tok.start[0], rules, m.group(2).strip()))
    return out


@dataclass
class LintResult:
    findings: list = field(default_factory=list)  # [Finding]
    suppressions: list = field(default_factory=list)  # [Suppression] (used ones)
    stats: dict = field(default_factory=dict)  # path -> {rule_id: stats}
    files: int = 0
    errors: list = field(default_factory=list)  # [(path, message)] parse failures
    rule_ids: list = field(default_factory=list)  # every registered rule id
    suppressed_counts: dict = field(default_factory=dict)  # rule_id -> int
    rule_stats: dict = field(default_factory=dict)  # project rule_id -> stats
    index_summary: dict = field(default_factory=dict)
    cache_info: dict = field(default_factory=dict)  # {"hits": n, "misses": n}

    def to_json(self) -> dict:
        """Stable machine-readable report (schema v2): EVERY registered rule
        gets a rollup — finding count, suppressed count, finding sites, and
        (for whole-program rules) the rule's own stats — plus the serialized
        project-index summary. Written to LINT.json by the tier-1 gate so
        the trajectory of findings AND suppressions is diffable across
        PRs."""
        by_rule: dict = {}
        for f in sorted(self.findings, key=lambda f: (f.rule, f.path, f.line)):
            by_rule.setdefault(f.rule, []).append(f.render())
        ids = (
            set(self.rule_ids)
            | set(by_rule)
            | set(self.suppressed_counts)
            | {BAD_SUPPRESSION, UNUSED_SUPPRESSION}
        ) - {"", "_index"}
        rules: dict = {}
        for rid in sorted(ids):
            entry = {
                "findings": len(by_rule.get(rid, ())),
                "suppressed": self.suppressed_counts.get(rid, 0),
                "sites": by_rule.get(rid, []),
            }
            if rid in self.rule_stats:
                entry["stats"] = self.rule_stats[rid]
            rules[rid] = entry
        sups = [
            {"at": f"{s.path}:{s.line}", "rules": list(s.rules), "reason": s.reason}
            for s in sorted(self.suppressions, key=lambda s: (s.path, s.line))
        ]
        out = {
            "version": 2,
            "files": self.files,
            "total": len(self.findings),
            "rules": rules,
            "suppressions": sups,
            "errors": [f"{p}: {m}" for p, m in sorted(self.errors)],
            "index": self.index_summary,
        }
        if self.cache_info:
            out["cache"] = self.cache_info
        return out


def default_rules() -> list:
    """Fresh instances of every shipped per-file rule (rules keep per-run
    state)."""
    from ray_tpu.analysis.rules_async import (
        BgStrongRef,
        LoopThreadRace,
        NoBlockingInAsync,
    )
    from ray_tpu.analysis.rules_buffers import (
        CountedSheds,
        CountedTransfers,
        CountedTrims,
    )
    from ray_tpu.analysis.rules_chaos import ChaosGate
    from ray_tpu.analysis.rules_fsm import FsmEmitter
    from ray_tpu.analysis.rules_security import MacBeforePickle

    return [
        BgStrongRef(),
        NoBlockingInAsync(),
        MacBeforePickle(),
        CountedTrims(),
        CountedSheds(),
        CountedTransfers(),
        LoopThreadRace(),
        FsmEmitter(),
        ChaosGate(),
    ]


def _all_rule_ids(rules: list, project_rules: list) -> set:
    ids = {r.id for r in rules} | {r.id for r in project_rules}
    ids |= {BAD_SUPPRESSION, UNUSED_SUPPRESSION}
    ids.discard("")
    ids.discard("_index")
    return ids


# ---------------------------------------------------------------------------
# Phase 1: per-file analysis -> serializable unit
# ---------------------------------------------------------------------------


def analyze_source(source: str, path: str, rules: list, known_ids: set) -> dict:
    """The cacheable unit of work: parse once, run the per-file rules and the
    index collector over one walk, scan suppressions. Returns a plain dict
    (JSON-able) so the parse cache can serve it verbatim."""
    from ray_tpu.analysis.index import IndexCollector, empty_contribution

    unit = {
        "raw": {},
        "sups": [],
        "bad": [],
        "stats": {},
        "index": empty_contribution(),
        "error": None,
    }
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        unit["error"] = f"syntax error: {e}"
        return unit
    lines = source.splitlines()
    active = [r for r in rules if r.applies_to(path)]
    active.append(IndexCollector())
    ctx = FileContext(path, tree, lines)
    for rule in active:
        rule.begin_file(ctx)
    _walk(tree, active, ctx)
    for rule in active:
        rule.end_file(ctx)
    unit["raw"] = {
        rid: [list(t) for t in entries]
        for rid, entries in ctx._raw_findings.items()
    }
    unit["stats"] = ctx.stats
    unit["index"] = ctx.index

    # Suppression scan. A disable WITH a reason is a candidate; a disable
    # WITHOUT one silences nothing and is itself a finding (the reason
    # string is the whole point — it is the written record of why the
    # invariant does not apply here).
    for s in parse_suppressions(path, source):
        # The comma continuation of the rule list can swallow the first
        # word of a prose reason ("disable=<rule>, intentional"): trailing
        # tokens that are not known rule ids belong to the reason.
        ids = list(s.rules)
        cut = next((i for i, r in enumerate(ids) if r not in known_ids), None)
        if cut is not None:
            s = Suppression(
                s.path,
                s.line,
                tuple(ids[:cut]),
                " ".join(ids[cut:] + ([s.reason] if s.reason else [])),
            )
        if not s.rules:
            unit["bad"].append([
                s.line,
                f"graftlint suppression names no known rule ({ids[0]!r} "
                "is not a rule id)",
            ])
            continue
        if not s.reason:
            unit["bad"].append([
                s.line,
                "graftlint suppression without a reason — write why the "
                "invariant does not apply here",
            ])
            continue
        unit["sups"].append([s.line, list(s.rules), s.reason])
    return unit


# ---------------------------------------------------------------------------
# Phase 2 + merge
# ---------------------------------------------------------------------------


def _finalize_file(
    path: str, unit: dict, phase2_raw: dict, result: LintResult
) -> None:
    """Apply this file's suppressions over the union of its phase-1 and
    phase-2 raw findings; a reasoned disable that matches NOTHING is itself
    a finding — the violation it excused was fixed, so the stale comment
    must go before it silently masks a future regression on that line."""
    if unit["error"] is not None:
        result.errors.append((path, unit["error"]))
        return
    for line, msg in unit["bad"]:
        result.findings.append(Finding(BAD_SUPPRESSION, path, line, msg))
    by_line: dict = {}
    for line, rules, reason in unit["sups"]:
        by_line.setdefault(line, []).append(
            Suppression(path, line, tuple(rules), reason)
        )
    merged: dict = {rid: list(v) for rid, v in unit["raw"].items()}
    for rid, entries in phase2_raw.items():
        merged.setdefault(rid, []).extend(entries)
    used: set = set()
    for rid in sorted(merged):
        for line, end, message in merged[rid]:
            sup = next(
                (
                    s
                    for ln in range(line, end + 1)
                    for s in by_line.get(ln, ())
                    if rid in s.rules
                ),
                None,
            )
            if sup is not None:
                used.add(id(sup))
                result.suppressed_counts[rid] = (
                    result.suppressed_counts.get(rid, 0) + 1
                )
                continue
            result.findings.append(Finding(rid, path, line, message))
    for sups in by_line.values():
        for s in sups:
            if id(s) in used:
                result.suppressions.append(s)
            else:
                result.findings.append(
                    Finding(
                        UNUSED_SUPPRESSION,
                        path,
                        s.line,
                        f"suppression for {'/'.join(s.rules)} matches no "
                        "finding on this line — remove the stale disable",
                    )
                )
    if unit["stats"]:
        result.stats[path] = unit["stats"]


def _run_pipeline(
    units: list,
    result: LintResult,
    rules: list,
    project_rules: list,
    readme: Optional[str] = None,
) -> None:
    """Fold the index, run the whole-program rules, merge + suppress."""
    from ray_tpu.analysis.index import ProjectIndex
    from ray_tpu.analysis.rules_xfile import ProjectContext

    index = ProjectIndex()
    for path, unit in units:
        if unit["error"] is None:
            index.add_file(path, unit["index"])
    if readme:
        index.add_readme_refs(readme)
    pctx = ProjectContext()
    for pr in project_rules:
        pr.check(index, pctx)
    unit_paths = {path for path, _ in units}
    for path, unit in units:
        _finalize_file(path, unit, pctx.raw.get(path, {}), result)
    # Findings against non-Python artifacts (README metric refs) have no
    # comment channel to suppress through — they are always live.
    for path in sorted(set(pctx.raw) - unit_paths):
        for rid, entries in sorted(pctx.raw[path].items()):
            for line, end, message in entries:
                result.findings.append(Finding(rid, path, line, message))
    result.rule_ids = sorted(_all_rule_ids(rules, project_rules))
    result.rule_stats = pctx.stats
    result.index_summary = index.summary()
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[list] = None,
    project_rules: Optional[list] = None,
) -> LintResult:
    """Lint one source string (the test-fixture entry point)."""
    return lint_sources({path: source}, rules=rules, project_rules=project_rules)


def lint_sources(
    sources: dict,
    rules: Optional[list] = None,
    project_rules: Optional[list] = None,
    readme: Optional[str] = None,
) -> LintResult:
    """Lint a {path: source} mapping through the full two-phase pipeline —
    the entry point for multi-file fixtures exercising cross-file rules."""
    from ray_tpu.analysis.rules_xfile import default_project_rules

    rules = default_rules() if rules is None else rules
    project_rules = (
        default_project_rules() if project_rules is None else project_rules
    )
    known_ids = _all_rule_ids(rules, project_rules)
    result = LintResult()
    units = []
    for path, source in sources.items():
        units.append((path, analyze_source(source, path, rules, known_ids)))
        result.files += 1
    _run_pipeline(units, result, rules, project_rules, readme=readme)
    return result


def iter_py_files(paths: Iterable[str]):
    seen: set = set()  # overlapping args must not double-lint a file

    def once(path: str):
        real = os.path.realpath(path)
        if real not in seen:
            seen.add(real)
            yield path

    for p in paths:
        if os.path.isfile(p):
            yield from once(p)
            continue
        for root, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames if not d.startswith(".") and d != "__pycache__"
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield from once(os.path.join(root, fn))


def lint_paths(
    paths: Iterable[str],
    rules: Optional[list] = None,
    project_rules: Optional[list] = None,
    cache_path: Optional[str] = None,
    readme: Optional[str] = None,
) -> LintResult:
    """Lint files/trees. With ``cache_path``, unchanged files skip phase 1
    entirely (their cached raw findings, suppressions, and index
    contributions are served by content identity); phase 2 always runs live
    over the full folded index."""
    from ray_tpu.analysis.cache import ParseCache
    from ray_tpu.analysis.rules_xfile import default_project_rules

    result = LintResult()
    rules = default_rules() if rules is None else rules
    project_rules = (
        default_project_rules() if project_rules is None else project_rules
    )
    known_ids = _all_rule_ids(rules, project_rules)
    paths = list(paths)
    for p in paths:
        # A typo'd path must not turn the gate green by linting nothing.
        if not os.path.exists(p):
            result.errors.append((p, "no such file or directory"))
    cache = ParseCache(cache_path) if cache_path else None
    units = []
    for path in iter_py_files(paths):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            result.errors.append((path, f"unreadable: {e}"))
            continue
        unit = cache.lookup(path, raw) if cache is not None else None
        if unit is None:
            try:
                source = raw.decode("utf-8")
            except UnicodeDecodeError as e:
                result.errors.append((path, f"unreadable: {e}"))
                continue
            unit = analyze_source(source, path, rules, known_ids)
            if cache is not None and unit["error"] is None:
                cache.store(path, raw, unit)
        units.append((path, unit))
        result.files += 1
    _run_pipeline(units, result, rules, project_rules, readme=readme)
    if cache is not None:
        cache.save()
        result.cache_info = {"hits": cache.hits, "misses": cache.misses}
    return result


def _walk(node: ast.AST, rules: list, ctx: FileContext) -> None:
    """Single document-order DFS; every rule sees every node. For function
    nodes, ONLY the body children enter the new scope: decorators, parameter
    defaults, and annotations evaluate at definition time on the defining
    thread, so a ``time.sleep`` inside a decorator argument of an
    ``async def`` is not a blocking call inside the coroutine."""
    for rule in rules:
        rule.visit(node, ctx)
    is_func = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
    is_class = isinstance(node, ast.ClassDef)
    if is_func:
        # A lambda body is deferred code exactly like a nested def's —
        # `run_in_executor(None, lambda: blocking())` must not read as
        # blocking inside the coroutine.
        body = node.body if isinstance(node.body, list) else [node.body]
        body_ids = set(map(id, body))
        outer = [c for c in ast.iter_child_nodes(node) if id(c) not in body_ids]
        for child in outer:
            ctx.parents[child] = node
            _walk(child, rules, ctx)
        ctx.func_stack.append(node)
        for child in body:
            ctx.parents[child] = node
            _walk(child, rules, ctx)
        ctx.func_stack.pop()
    else:
        if is_class:
            ctx.class_stack.append(node)
        for child in ast.iter_child_nodes(node):
            ctx.parents[child] = node
            _walk(child, rules, ctx)
        if is_class:
            ctx.class_stack.pop()
    for rule in rules:
        rule.leave(node, ctx)
