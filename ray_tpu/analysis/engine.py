"""graftlint engine: one parse per file, rule visitors multiplexed over one walk.

The invariants this codebase learned the hard way (GC-killed fire-and-forget
asyncio tasks, blocking calls on the event-loop thread, pickle of
unauthenticated wire bytes, silent bounded-buffer trims) keep re-appearing as
review comments. This package machine-checks them: each rule is an AST
visitor; the engine parses each file ONCE and drives every applicable rule
over a single depth-first walk (lexical order, parent links and scope stacks
maintained by the engine so rules stay small).

Suppression: ``# graftlint: disable=<rule>[,<rule>...]  <reason>`` on the
finding's line. The reason is REQUIRED — a disable comment without one does
not suppress and is itself reported (rule id ``bad-suppression``). Reasons
are carried into the JSON report so the suppression inventory is diffable
across PRs.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize as _tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional

# Matches the inline disable directive (syntax in the module docstring —
# spelling it here would make this comment parse as a directive itself).
# The rule list tolerates spaces around commas ("rule-a, rule-b").
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable="
    r"([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)[ \t]*[-—:]*[ \t]*(.*?)\s*$"
)

BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"


@dataclass(frozen=True)
class Finding:
    """One rule violation: file:line, rule id, one-line explanation."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Suppression:
    path: str
    line: int
    rules: tuple
    reason: str


class Rule:
    """Base class for graftlint rules.

    Subclasses set ``id`` and ``explanation`` and override any of the hook
    methods. ``visit`` runs on every node in document order (parents before
    children); ``leave`` runs after a node's subtree completes. Rules report
    via ``ctx.report(node_or_line, message)``.
    """

    id: str = ""
    explanation: str = ""

    def applies_to(self, path: str) -> bool:
        return True

    def begin_file(self, ctx: "FileContext") -> None:
        pass

    def visit(self, node: ast.AST, ctx: "FileContext") -> None:
        pass

    def leave(self, node: ast.AST, ctx: "FileContext") -> None:
        pass

    def end_file(self, ctx: "FileContext") -> None:
        pass


class FileContext:
    """Per-file state the engine maintains for every rule: source path and
    lines, parent links, and the enclosing function/class stacks."""

    def __init__(self, path: str, tree: ast.Module, lines: list):
        self.path = path
        self.tree = tree
        self.lines = lines  # 0-indexed source lines (for suppression lookup)
        self.parents: dict = {}
        # Innermost-last stacks. func_stack holds FunctionDef/AsyncFunctionDef
        # nodes; class_stack holds ClassDef nodes.
        self.func_stack: list = []
        self.class_stack: list = []
        self._raw_findings: dict = {}  # rule_id -> [ (line, message) ]
        self.stats: dict = {}  # rule_id -> arbitrary JSON-able stats

    # -- helpers rules lean on ------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def in_async_context(self) -> bool:
        """True when the innermost enclosing function is ``async def`` — a
        nested plain ``def`` (executor thunk, callback) exits the async
        context even though an async function encloses it lexically."""
        return bool(self.func_stack) and isinstance(
            self.func_stack[-1], ast.AsyncFunctionDef
        )

    def outermost_function(self) -> Optional[ast.AST]:
        return self.func_stack[0] if self.func_stack else None

    def report(self, rule: Rule, node, message: str = "") -> None:
        """``node`` may be an AST node, a bare line int, or a
        ``(line, end_line)`` span. The whole extent matters: a disable
        comment belongs on the line a formatter puts it — often the CLOSING
        line of a multi-line statement — and must still match, so rules
        that buffer findings keep spans, not bare ints."""
        if isinstance(node, int):
            line = end = node
        elif isinstance(node, tuple):
            line, end = node
        else:
            line = getattr(node, "lineno", 0)
            end = getattr(node, "end_lineno", None) or line
        self._raw_findings.setdefault(rule.id, []).append(
            (line, end, message or rule.explanation)
        )


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def parse_suppressions(path: str, source: str) -> list:
    """Disable directives from actual COMMENT tokens only — a
    "# graftlint: disable=..." spelled inside a string literal (test
    fixtures, docs) is data, not a directive."""
    out = []
    if "graftlint" not in source:
        return out
    try:
        tokens = list(_tokenize.generate_tokens(io.StringIO(source).readline))
    except (_tokenize.TokenError, IndentationError, SyntaxError):
        return out  # un-tokenizable source already surfaced as a parse error
    for tok in tokens:
        if tok.type != _tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if m is None:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        out.append(Suppression(path, tok.start[0], rules, m.group(2).strip()))
    return out


@dataclass
class LintResult:
    findings: list = field(default_factory=list)  # [Finding]
    suppressions: list = field(default_factory=list)  # [Suppression] (valid ones)
    stats: dict = field(default_factory=dict)  # path -> {rule_id: stats}
    files: int = 0
    errors: list = field(default_factory=list)  # [(path, message)] parse failures

    def to_json(self) -> dict:
        """Stable machine-readable report: rule -> sorted [file:line ...].
        Written to LINT.json by the tier-1 wrapper test so the trajectory of
        findings AND suppressions is diffable across PRs."""
        rules: dict = {}
        for f in sorted(self.findings, key=lambda f: (f.rule, f.path, f.line)):
            rules.setdefault(f.rule, []).append(f.render())
        sups = [
            {"at": f"{s.path}:{s.line}", "rules": list(s.rules), "reason": s.reason}
            for s in sorted(self.suppressions, key=lambda s: (s.path, s.line))
        ]
        return {
            "version": 1,
            "files": self.files,
            "total": len(self.findings),
            "rules": rules,
            "suppressions": sups,
            "errors": [f"{p}: {m}" for p, m in sorted(self.errors)],
        }


def default_rules() -> list:
    """Fresh instances of every shipped rule (rules keep per-run state)."""
    from ray_tpu.analysis.rules_async import (
        BgStrongRef,
        LoopThreadRace,
        NoBlockingInAsync,
    )
    from ray_tpu.analysis.rules_buffers import (
        CountedSheds,
        CountedTransfers,
        CountedTrims,
    )
    from ray_tpu.analysis.rules_chaos import ChaosGate
    from ray_tpu.analysis.rules_fsm import FsmEmitter
    from ray_tpu.analysis.rules_security import MacBeforePickle

    return [
        BgStrongRef(),
        NoBlockingInAsync(),
        MacBeforePickle(),
        CountedTrims(),
        CountedSheds(),
        CountedTransfers(),
        LoopThreadRace(),
        FsmEmitter(),
        ChaosGate(),
    ]


def lint_source(
    source: str, path: str = "<string>", rules: Optional[list] = None
) -> LintResult:
    """Lint one source string (the test-fixture entry point)."""
    result = LintResult()
    _lint_one(source, path, default_rules() if rules is None else rules, result)
    result.files = 1
    return result


def _lint_one(source: str, path: str, rules: list, result: LintResult) -> None:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        result.errors.append((path, f"syntax error: {e}"))
        return
    lines = source.splitlines()
    active = [r for r in rules if r.applies_to(path)]
    ctx = FileContext(path, tree, lines)
    for rule in active:
        rule.begin_file(ctx)
    _walk(tree, active, ctx)
    for rule in active:
        rule.end_file(ctx)
    if ctx.stats:
        result.stats[path] = ctx.stats

    # Suppression pass: a disable WITH a reason silences same-line findings
    # of the named rules; a disable WITHOUT one silences nothing and is
    # itself a finding (the reason string is the whole point — it is the
    # written record of why the invariant does not apply here). A reasoned
    # disable that matches NOTHING is also a finding: the violation it
    # excused was fixed, so the stale comment must go before it silently
    # masks a future regression reintroduced on that line.
    by_line: dict = {}
    known_ids = {r.id for r in rules} | {BAD_SUPPRESSION, UNUSED_SUPPRESSION}
    for s in parse_suppressions(path, source):
        # The comma continuation of the rule list can swallow the first
        # word of a prose reason ("disable=<rule>, intentional"): trailing
        # tokens that are not known rule ids belong to the reason.
        ids = list(s.rules)
        cut = next((i for i, r in enumerate(ids) if r not in known_ids), None)
        if cut is not None:
            s = Suppression(
                s.path,
                s.line,
                tuple(ids[:cut]),
                " ".join(ids[cut:] + ([s.reason] if s.reason else [])),
            )
        if not s.rules:
            result.findings.append(
                Finding(
                    BAD_SUPPRESSION,
                    path,
                    s.line,
                    f"graftlint suppression names no known rule ({ids[0]!r} "
                    "is not a rule id)",
                )
            )
            continue
        if not s.reason:
            result.findings.append(
                Finding(
                    BAD_SUPPRESSION,
                    path,
                    s.line,
                    "graftlint suppression without a reason — write why the "
                    "invariant does not apply here",
                )
            )
            continue
        by_line.setdefault(s.line, []).append(s)
    used: set = set()
    for rule in active:
        for line, end, message in ctx._raw_findings.get(rule.id, ()):
            sup = next(
                (
                    s
                    for ln in range(line, end + 1)
                    for s in by_line.get(ln, ())
                    if rule.id in s.rules
                ),
                None,
            )
            if sup is not None:
                used.add(id(sup))
                continue
            result.findings.append(Finding(rule.id, path, line, message))
    for sups in by_line.values():
        for s in sups:
            if id(s) in used:
                result.suppressions.append(s)
            else:
                result.findings.append(
                    Finding(
                        UNUSED_SUPPRESSION,
                        path,
                        s.line,
                        f"suppression for {'/'.join(s.rules)} matches no "
                        "finding on this line — remove the stale disable",
                    )
                )


def iter_py_files(paths: Iterable[str]):
    seen: set = set()  # overlapping args must not double-lint a file

    def once(path: str):
        real = os.path.realpath(path)
        if real not in seen:
            seen.add(real)
            yield path

    for p in paths:
        if os.path.isfile(p):
            yield from once(p)
            continue
        for root, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames if not d.startswith(".") and d != "__pycache__"
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield from once(os.path.join(root, fn))


def lint_paths(paths: Iterable[str], rules: Optional[list] = None) -> LintResult:
    result = LintResult()
    rules = default_rules() if rules is None else rules
    paths = list(paths)
    for p in paths:
        # A typo'd path must not turn the gate green by linting nothing.
        if not os.path.exists(p):
            result.errors.append((p, "no such file or directory"))
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            result.errors.append((path, f"unreadable: {e}"))
            continue
        _lint_one(source, path, rules, result)
        result.files += 1
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result


def _walk(node: ast.AST, rules: list, ctx: FileContext) -> None:
    """Single document-order DFS; every rule sees every node. For function
    nodes, ONLY the body children enter the new scope: decorators, parameter
    defaults, and annotations evaluate at definition time on the defining
    thread, so a ``time.sleep`` inside a decorator argument of an
    ``async def`` is not a blocking call inside the coroutine."""
    for rule in rules:
        rule.visit(node, ctx)
    is_func = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
    is_class = isinstance(node, ast.ClassDef)
    if is_func:
        # A lambda body is deferred code exactly like a nested def's —
        # `run_in_executor(None, lambda: blocking())` must not read as
        # blocking inside the coroutine.
        body = node.body if isinstance(node.body, list) else [node.body]
        body_ids = set(map(id, body))
        outer = [c for c in ast.iter_child_nodes(node) if id(c) not in body_ids]
        for child in outer:
            ctx.parents[child] = node
            _walk(child, rules, ctx)
        ctx.func_stack.append(node)
        for child in body:
            ctx.parents[child] = node
            _walk(child, rules, ctx)
        ctx.func_stack.pop()
    else:
        if is_class:
            ctx.class_stack.append(node)
        for child in ast.iter_child_nodes(node):
            ctx.parents[child] = node
            _walk(child, rules, ctx)
        if is_class:
            ctx.class_stack.pop()
    for rule in rules:
        rule.leave(node, ctx)
