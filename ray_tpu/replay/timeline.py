"""Declarative chaos timeline: phase-anchored actions for a replay run.

A timeline names actions against the trace's phases ("slow replicas during
the storm", "TPU preemption notice early in recovery", "weight publication
mid-recovery") and compiles them into two artifacts:

* **Seeded fault rules** for the existing :class:`FaultSchedule`. Chaos
  rules fire on deterministic *hit counters*, not wall clocks — so the
  compiler projects time anchors into hit space: a slow-replica window
  [a, b) becomes ``skip = requests arriving before a`` and ``max_faults =
  requests inside the window`` (counted off the trace itself), and a
  preemption notice at wall offset *t* becomes ``nth = t / heartbeat``
  on the victim's ``tpu.preempt`` gate. The projection is approximate in
  wall time (shedding shifts hits, and sites that fire in replica
  processes count hits per process), but EXACT in replay space: two
  same-seed runs fire the same rules at the same hit numbers, which is
  the determinism the acceptance diff asserts.

* **Control-plane actions** the driver executes on the (warped) wall
  clock during the run — things that are cluster *operations* rather than
  injected faults, e.g. a mid-run checkpoint publication. Their wall
  timing does not participate in the injection-log identity; their
  effects flow through the normal seeded gates (``ckpt.publish.swap``).

One seed therefore replays the whole day: trace bytes, fault sequence,
and action order.
"""
from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ray_tpu import chaos as _chaos

ACTIONS = ("slow_replica_window", "client_flap", "tpu_preempt",
           "publish_weights", "chaos_rule")


@dataclass
class CompiledTimeline:
    """What ``Timeline.compile`` hands the scenario: an installable chaos
    spec, the wall-clock action list, and the phase spans (trace seconds)
    the ledger's per-phase stats reuse."""

    spec: dict
    control: list = field(default_factory=list)  # [(t_trace_s, action), ...]
    spans: dict = field(default_factory=dict)


class Timeline:
    """``spans``: phase name -> (t0, t1) in trace seconds (usually
    ``trace.phase_spans(params)``). ``actions``: a list of dicts, each with
    an ``action`` from :data:`ACTIONS` plus a ``phase`` / ``offset_s``
    anchor; see the compiler for per-action fields."""

    def __init__(self, spans: dict, actions: list):
        self.spans = dict(spans)
        self.actions = list(actions)
        for a in self.actions:
            if a.get("action") not in ACTIONS:
                raise ValueError(f"unknown timeline action {a.get('action')!r} "
                                 f"(known: {ACTIONS})")
            if "phase" in a and a["phase"] not in self.spans:
                raise ValueError(f"action {a['action']!r} anchors to unknown "
                                 f"phase {a['phase']!r} (have: {sorted(self.spans)})")

    def _anchor(self, a: dict) -> float:
        lo, _hi = self.spans[a["phase"]] if "phase" in a else (0.0, 0.0)
        return lo + float(a.get("offset_s", 0.0))

    def _window(self, a: dict) -> tuple[float, float]:
        t0 = self._anchor(a)
        if a.get("duration_s") is not None:
            return t0, t0 + float(a["duration_s"])
        _lo, hi = self.spans[a["phase"]] if "phase" in a else (0.0, t0)
        return t0, hi  # default: to the end of the anchoring phase

    def compile(self, seed: int, records: list, *, time_warp: float = 1.0,
                heartbeat_s: float = 0.2,
                lead_s: float = 3.0) -> CompiledTimeline:
        """Project every action into rules/control entries. ``records`` is
        the synthesized trace (hit-space projection source); ``lead_s``
        estimates the wall time between schedule install and replay start
        (cluster + app bring-up) for gates whose hits accrue from process
        start, e.g. heartbeat-driven ``tpu.preempt``."""
        arrivals = [r["t"] for r in records]

        def hits_before(t: float) -> int:
            return bisect.bisect_left(arrivals, t)

        rules: list = []
        control: list = []
        for a in self.actions:
            kind = a["action"]
            if kind == "slow_replica_window":
                t0, t1 = self._window(a)
                rule = {"site": "serve.replica.slow", "kind": "delay",
                        "delay_s": float(a.get("delay_s", 0.03)),
                        "skip": hits_before(t0),
                        "max_faults": max(1, hits_before(t1) - hits_before(t0))}
                if a.get("deployment"):
                    rule["ctx"] = {"deployment": a["deployment"]}
                rules.append(rule)
            elif kind == "client_flap":
                t0, t1 = self._window(a)
                rules.append({
                    "site": "replay.request.send",
                    "kind": a.get("kind", "delay"),
                    "delay_s": float(a.get("delay_s", 0.05)),
                    "every": int(a.get("every", 7)),
                    "skip": hits_before(t0),
                    "max_faults": max(1, (hits_before(t1) - hits_before(t0))
                                      // max(1, int(a.get("every", 7)))),
                })
            elif kind == "tpu_preempt":
                t_wall = lead_s + self._anchor(a) / time_warp
                rule = {"site": "tpu.preempt", "kind": "preempt",
                        "nth": max(1, int(t_wall / max(heartbeat_s, 1e-3))),
                        "delay_s": float(a.get("grace_s", 0.4))}
                ctx = {k: a[k] for k in ("worker_id", "slice") if k in a}
                if ctx:
                    rule["ctx"] = ctx
                rules.append(rule)
            elif kind == "chaos_rule":
                rules.append(dict(a["rule"]))
            else:  # control-plane: executed on the wall clock by the driver
                control.append((self._anchor(a), dict(a)))
        spec = {"seed": int(seed), "rules": rules}
        _chaos.FaultSchedule.from_spec(spec)  # fail loud on a bad site/kind now
        control.sort(key=lambda x: x[0])
        return CompiledTimeline(spec=spec, control=control, spans=self.spans)


class TimelineDriver:
    """Executes a compiled timeline's control-plane actions at their warped
    wall offsets while the replayer runs. ``handlers`` maps action name ->
    callable(action_dict) -> detail; outcomes land in ``log`` (the ledger
    embeds it, so a run report shows what the timeline actually did and
    how late each action fired)."""

    def __init__(self, control: list, handlers: dict, *,
                 time_warp: float = 1.0):
        self.control = list(control)
        self.handlers = dict(handlers)
        self.time_warp = float(time_warp)
        self.log: list = []
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TimelineDriver":
        self._thread = threading.Thread(
            target=self._run, name="raytpu-timeline", daemon=True)
        self._t0 = time.perf_counter()
        self._thread.start()
        return self

    def _run(self):
        for t_trace, action in self.control:
            delay = self._t0 + t_trace / self.time_warp - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            entry = {"t": t_trace, "action": action["action"],
                     "late_s": round(time.perf_counter()
                                     - (self._t0 + t_trace / self.time_warp), 3)}
            fn: Optional[Callable] = self.handlers.get(action["action"])
            try:
                if fn is None:
                    raise KeyError(f"no handler for {action['action']!r}")
                entry["detail"] = fn(action)
                entry["ok"] = True
            except Exception as e:  # noqa: BLE001 - recorded, never raised mid-run
                entry["ok"] = False
                entry["detail"] = f"{type(e).__name__}: {e}"
            self.log.append(entry)

    def join(self, timeout: float = 60.0) -> list:
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        return list(self.log)
