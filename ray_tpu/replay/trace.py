"""Versioned workload-trace format + seeded synthesizer.

A trace is the replay plane's unit of record: one JSONL file whose first
line is a header and whose remaining lines are per-request records sorted
by arrival offset. The serialization is CANONICAL (sorted keys, no
whitespace, fixed float rounding), so the same header + records always
produce the same bytes — and the synthesizer below is a pure function of
its seed + params, so ``synthesize(seed) -> write_trace`` is byte-identical
across runs and platforms. That byte identity is the replay contract's
first half (the chaos plane's seeded FaultSchedule is the second): a
day-in-the-life run is reproducible from ONE integer.

Header (line 1)::

    {"format": "raytpu-trace", "version": 1, "seed": 0,
     "duration_s": 16.0, "requests": 412,
     "classes": {"interactive": 91, ...}, "tenants": {"t0": 202, ...},
     "params": {...synthesizer params...}}

Record (one per line, sorted by ``t``)::

    {"i": 0, "t": 0.013, "cls": "interactive", "tenant": "t0",
     "route": "/day", "size": 186, "stream": 1, "timeout_s": 2.0}

``t`` is the arrival offset in seconds from replay start, ``size`` the
request payload in bytes (a prompt-size proxy), ``stream`` whether the
client expects a chunked token stream (TTFT is recorded for these), and
``timeout_s`` the client deadline the replayer maps onto the
``x-request-timeout-s`` ingress header.

The synthesizer shapes the mix after a production day compressed into the
trace window: a diurnal envelope (calm -> spike -> recovery), Zipf tenant
skew (a few tenants dominate), and a streaming/batch blend per QoS class.
"""
from __future__ import annotations

import hashlib
import json
import random
from typing import Optional

FORMAT = "raytpu-trace"
VERSION = 1

# Class mix: (weight, timeout_s, stream probability, payload lognormal mu).
# interactive = the protected foreground; batch = throughput lane;
# best_effort = the floodable background that the storm multiplies.
_CLASSES = {
    "interactive": {"weight": 0.25, "timeout_s": 2.0, "p_stream": 0.4, "size_mu": 5.0},
    "batch": {"weight": 0.25, "timeout_s": 1.5, "p_stream": 0.0, "size_mu": 7.0},
    "best_effort": {"weight": 0.5, "timeout_s": 1.0, "p_stream": 0.0, "size_mu": 5.5},
}


def default_params(quick: bool = False) -> dict:
    """The day_in_the_life scenario's synthesizer params (shared with the
    canonical committed artifact so tests can assert the generator never
    drifts). Trace time is pre-warp: quick mode replays at time_warp 2."""
    if quick:
        return {"duration_s": 16.0, "base_rps": 26.0, "spike_mult": 3.0,
                "spike_start": 0.35, "spike_end": 0.7, "tenants": 4,
                "zipf_alpha": 1.2, "route": "/day"}
    return {"duration_s": 45.0, "base_rps": 40.0, "spike_mult": 3.0,
            "spike_start": 0.35, "spike_end": 0.7, "tenants": 6,
            "zipf_alpha": 1.2, "route": "/day"}


def envelope(frac: float, spike_start: float, spike_end: float,
             spike_mult: float) -> float:
    """Diurnal rate multiplier at ``frac`` of the trace (0..1): 1.0 on the
    calm shoulders, ``spike_mult`` across the spike window, with short
    linear ramps (10% of the window each side) so the storm has an onset
    the autoscaler/SLO trajectory can be read against."""
    ramp = max(1e-6, 0.1 * (spike_end - spike_start))
    if frac < spike_start or frac >= spike_end:
        return 1.0
    up = min(1.0, (frac - spike_start) / ramp)
    down = min(1.0, (spike_end - frac) / ramp)
    return 1.0 + (spike_mult - 1.0) * min(up, down)


def phase_spans(params: dict) -> dict:
    """The three named phases in TRACE seconds — the anchor space the chaos
    timeline and the ledger's per-phase stats both use."""
    d = float(params["duration_s"])
    s0, s1 = params["spike_start"] * d, params["spike_end"] * d
    return {"calm": (0.0, s0), "storm": (s0, s1), "recovery": (s1, d)}


def synthesize(seed: int, *, duration_s: float, base_rps: float,
               spike_mult: float = 3.0, spike_start: float = 0.35,
               spike_end: float = 0.7, tenants: int = 4,
               zipf_alpha: float = 1.2, route: str = "/day") -> tuple[dict, list]:
    """Pure function of (seed, params) -> (header, records). Arrivals are an
    inhomogeneous Poisson process via thinning (exponential inter-arrivals
    at the peak rate, accepted with probability rate(t)/peak); tenant draws
    are Zipf-weighted; class/stream/size/jitter all come from the same
    seeded generator, so the whole trace replays from one integer."""
    rng = random.Random(seed)
    peak = base_rps * spike_mult
    tenant_names = [f"t{i}" for i in range(tenants)]
    tenant_w = [1.0 / (i + 1) ** zipf_alpha for i in range(tenants)]
    classes = sorted(_CLASSES)
    class_w = [_CLASSES[c]["weight"] for c in classes]
    records = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        if rng.random() >= envelope(t / duration_s, spike_start, spike_end,
                                    spike_mult) / spike_mult:
            continue  # thinned: instantaneous rate below peak
        cls = rng.choices(classes, weights=class_w)[0]
        spec = _CLASSES[cls]
        records.append({
            "i": len(records),
            "t": round(t, 6),
            "cls": cls,
            "tenant": rng.choices(tenant_names, weights=tenant_w)[0],
            "route": route,
            "size": max(16, int(rng.lognormvariate(spec["size_mu"], 0.6))),
            "stream": 1 if rng.random() < spec["p_stream"] else 0,
            "timeout_s": round(spec["timeout_s"] * rng.uniform(0.9, 1.1), 3),
        })
    by_cls: dict = {}
    by_tenant: dict = {}
    for r in records:
        by_cls[r["cls"]] = by_cls.get(r["cls"], 0) + 1
        by_tenant[r["tenant"]] = by_tenant.get(r["tenant"], 0) + 1
    header = {
        "format": FORMAT, "version": VERSION, "seed": int(seed),
        "duration_s": round(float(duration_s), 6), "requests": len(records),
        "classes": by_cls, "tenants": by_tenant,
        "params": {"base_rps": base_rps, "spike_mult": spike_mult,
                   "spike_start": spike_start, "spike_end": spike_end,
                   "tenants": tenants, "zipf_alpha": zipf_alpha,
                   "route": route},
    }
    return header, records


def _canon(obj: dict) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def dumps_trace(header: dict, records: list) -> bytes:
    """Canonical bytes for a trace: the byte-identity surface."""
    lines = [_canon(header)] + [_canon(r) for r in records]
    return ("\n".join(lines) + "\n").encode()


def write_trace(path: str, header: dict, records: list) -> str:
    """Write the canonical JSONL file; returns its sha256 hex digest (the
    ledger embeds it so a report names exactly the trace that produced it)."""
    blob = dumps_trace(header, records)
    with open(path, "wb") as f:
        f.write(blob)
    return hashlib.sha256(blob).hexdigest()


def trace_sha256(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def read_trace(path: str) -> tuple[dict, list]:
    """Parse + validate one trace file -> (header, records)."""
    with open(path, "r", encoding="utf-8") as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"trace {path!r} is empty")
    header = json.loads(lines[0])
    if header.get("format") != FORMAT:
        raise ValueError(f"not a {FORMAT} file: {header.get('format')!r}")
    if int(header.get("version", -1)) > VERSION:
        raise ValueError(
            f"trace version {header.get('version')} is newer than this "
            f"reader (max {VERSION})")
    records = [json.loads(ln) for ln in lines[1:]]
    if len(records) != int(header.get("requests", len(records))):
        raise ValueError(
            f"trace header promises {header.get('requests')} requests, "
            f"file holds {len(records)}")
    last = -1.0
    for r in records:
        if r["t"] < last:
            raise ValueError(f"record {r['i']} out of arrival order")
        last = r["t"]
    return header, records
