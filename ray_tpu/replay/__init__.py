"""Day-in-the-life replay plane: trace-driven load against a live cluster.

Three pieces, composed by the ``day_in_the_life`` chaos scenario:

* :mod:`ray_tpu.replay.trace` — versioned JSONL workload traces + a seeded
  synthesizer (same seed => byte-identical file);
* :mod:`ray_tpu.replay.runner` — an open-loop replayer that fires records
  at trace-faithful arrival times onto the QoS ingress headers;
* :mod:`ray_tpu.replay.timeline` — a declarative, phase-anchored chaos
  timeline compiled onto the seeded :class:`~ray_tpu.chaos.plan.FaultSchedule`
  plus wall-clock control-plane actions.

The run's observability exhaust is folded into one diffable report by
:mod:`ray_tpu.obs.ledger`.
"""
from ray_tpu.replay.runner import Replayer, summarize
from ray_tpu.replay.timeline import CompiledTimeline, Timeline, TimelineDriver
from ray_tpu.replay.trace import (default_params, dumps_trace, envelope,
                                  phase_spans, read_trace, synthesize,
                                  trace_sha256, write_trace)

__all__ = [
    "CompiledTimeline", "Replayer", "Timeline", "TimelineDriver",
    "default_params", "dumps_trace", "envelope", "phase_spans", "read_trace",
    "summarize", "synthesize", "trace_sha256", "write_trace",
]
