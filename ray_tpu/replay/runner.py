"""Open-loop trace replayer against the serve HTTP ingress.

Open loop means arrival-faithful: request *i* fires at ``t0 + t_i /
time_warp`` whether or not earlier requests completed — a saturated server
sees the full offered load and must shed, exactly like production (a
closed-loop client would politely back off and hide the overload). Each
record maps onto the QoS ingress the proxy already speaks::

    x-priority          <- record cls
    x-tenant            <- record tenant
    x-request-timeout-s <- record timeout_s (scaled by the warp)
    x-stream: 1         <- record stream (the deployment answers chunked)

Per-request outcomes (status, latency, TTFT for streams, scheduling error)
feed the run ledger (obs/ledger.py). A chaos gate ``replay.request.send``
sits on the send path so a timeline can inject client-side network flap
(drops/delays) with the same seeded determinism as every other site — the
replayer is part of the system under replay, not an outside observer.
"""
from __future__ import annotations

import concurrent.futures
import http.client
import time
from typing import Optional

from ray_tpu import chaos as _chaos


def percentile(values: list, q: float) -> Optional[float]:
    """Nearest-rank percentile of an unsorted list (None when empty)."""
    if not values:
        return None
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(len(vals) * q))]


class Replayer:
    """Fire one trace at a live proxy port. ``time_warp`` > 1 compresses
    trace time (quick mode: a 16 s trace replays in 8 s at warp 2); client
    timeouts are scaled down by the same factor so deadline behaviour is
    warp-invariant."""

    def __init__(self, port: int, *, host: str = "127.0.0.1",
                 time_warp: float = 1.0, max_workers: int = 24,
                 connect_timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.time_warp = float(time_warp)
        self.max_workers = int(max_workers)
        self.connect_timeout_s = float(connect_timeout_s)

    # -- one request ------------------------------------------------------
    def _fire(self, rec: dict, t0: float) -> dict:
        sched = t0 + rec["t"] / self.time_warp
        out = {"i": rec["i"], "cls": rec["cls"], "tenant": rec["tenant"],
               "t": rec["t"], "stream": rec.get("stream", 0),
               "code": -1, "latency_s": 0.0, "ttft_s": None, "late_s": 0.0}
        fault = _chaos.maybe_inject("replay.request.send",
                                    cls=rec["cls"], tenant=rec["tenant"])
        if fault is not None:
            if fault.kind == "drop":
                out["code"] = 0  # client-side loss: never reached the wire
                return out
            time.sleep(fault.delay_s)
        send = time.perf_counter()
        out["late_s"] = round(send - sched, 6)
        timeout = max(0.2, rec["timeout_s"] / self.time_warp)
        headers = {
            "x-priority": rec["cls"],
            "x-tenant": rec["tenant"],
            "x-request-timeout-s": f"{timeout:g}",
            "content-type": "application/json",
        }
        if rec.get("stream"):
            headers["x-stream"] = "1"
        body = b"x" * int(rec.get("size", 0))
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.connect_timeout_s)
        try:
            conn.request("POST", rec.get("route", "/"), body=body,
                         headers=headers)
            resp = conn.getresponse()
            out["code"] = resp.status
            first = resp.read(1)  # returns with the first body chunk
            if rec.get("stream") and resp.status == 200 and first:
                out["ttft_s"] = round(time.perf_counter() - send, 6)
            resp.read()
        except Exception:
            out["code"] = -1  # transport-level failure (counted, never raised)
        finally:
            conn.close()
        out["latency_s"] = round(time.perf_counter() - send, 6)
        return out

    # -- the open loop ----------------------------------------------------
    def run(self, header: dict, records: list) -> list:
        """Replay every record at its scheduled arrival; returns the outcome
        list in record order. The dispatcher thread only sleeps + submits;
        sends run on a bounded pool (a slow server delays *responses*, not
        later *arrivals* — until the client itself runs out of senders,
        which is the open-loop client-capacity limit and is visible in the
        recorded ``late_s``)."""
        outcomes: list = [None] * len(records)
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.max_workers,
                thread_name_prefix="raytpu-replay") as pool:
            futs = []
            for rec in records:
                delay = t0 + rec["t"] / self.time_warp - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                futs.append((rec["i"], pool.submit(self._fire, rec, t0)))
            for i, fut in futs:
                outcomes[i] = fut.result()
        return outcomes


def summarize(outcomes: list, phases: Optional[dict] = None) -> dict:
    """Fold raw outcomes into per-class (x tenant, x phase) stat buckets —
    the shape the ledger embeds. ``phases`` maps name -> (t0, t1) in trace
    seconds; a record belongs to the phase its *arrival* falls in."""
    def bucket(rows: list) -> dict:
        ok = [r for r in rows if r["code"] == 200]
        lat = [r["latency_s"] for r in ok]
        ttft = [r["ttft_s"] for r in ok if r["ttft_s"] is not None]
        n = len(rows)
        return {
            "n": n,
            "ok": len(ok),
            "goodput": round(len(ok) / n, 4) if n else None,
            "shed": sum(1 for r in rows if r["code"] == 429),
            "expired": sum(1 for r in rows if r["code"] == 504),
            "errors": sum(1 for r in rows if r["code"] in (-1, 500)),
            "client_dropped": sum(1 for r in rows if r["code"] == 0),
            "p50_s": percentile(lat, 0.50),
            "p95_s": percentile(lat, 0.95),
            "p99_s": percentile(lat, 0.99),
            "ttft_p95_s": percentile(ttft, 0.95),
            "late_p99_s": percentile([r["late_s"] for r in rows], 0.99),
        }

    rows = [r for r in outcomes if r is not None]
    out: dict = {"total": bucket(rows), "classes": {}}
    for cls in sorted({r["cls"] for r in rows}):
        crows = [r for r in rows if r["cls"] == cls]
        entry: dict = {"_total": bucket(crows), "tenants": {}, "phases": {}}
        for tenant in sorted({r["tenant"] for r in crows}):
            entry["tenants"][tenant] = bucket(
                [r for r in crows if r["tenant"] == tenant])
        for name, (lo, hi) in (phases or {}).items():
            entry["phases"][name] = bucket(
                [r for r in crows if lo <= r["t"] < hi])
        out["classes"][cls] = entry
    return out
