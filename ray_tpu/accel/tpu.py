"""TPU accelerator manager: topology detection, labels, chip isolation.

Role-equivalent to the reference's TPU accelerator plugin
(/root/reference/python/ray/_private/accelerators/tpu.py, 683 LoC): autodetect
the slice from GCE metadata / GKE env vars (tpu.py:19-35 uses
TPU_ACCELERATOR_TYPE / TPU_TOPOLOGY / TPU_NAME / TPU_WORKER_ID), compute
chips-per-host (tpu.py:136), validate topology strings (tpu.py:89), expose
TPU_VISIBLE_CHIPS-style isolation (tpu.py:37), and advertise node labels
(slice name, worker id, pod type) plus the ``TPU-{pod}-head`` gang-resource
on worker 0 (tpu.py:224 reserve_tpu_slice).

No GCE metadata server is assumed here: detection is env-first, with a JAX
fallback on real TPU hosts. This module must stay importable without jax.
"""
from __future__ import annotations

import os
import re
from typing import Optional

# Node label keys (reference: ray_constants RAY_NODE_TPU_* keys).
TPU_SLICE_NAME_LABEL = "raytpu.io/tpu-slice-name"
TPU_WORKER_ID_LABEL = "raytpu.io/tpu-worker-id"
TPU_POD_TYPE_LABEL = "raytpu.io/tpu-pod-type"
TPU_TOPOLOGY_LABEL = "raytpu.io/tpu-topology"
TPU_VERSION_LABEL = "raytpu.io/tpu-version"

VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"

# generation -> chips per host for full hosts (v4/v5p: 4 chips/host;
# v5e/v6e: 8 for 16+ chip slices, else chips==slice size on one host).
_GEN_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5litepod": 8, "v5e": 8, "v6e": 8}


def _accelerator_type() -> Optional[str]:
    return os.environ.get("TPU_ACCELERATOR_TYPE")


def parse_accelerator_type(acc_type: str) -> tuple[str, int]:
    """'v4-16' -> ('v4', 16 logical devices); 'v5litepod-8' -> ('v5litepod', 8)."""
    m = re.fullmatch(r"(v\d+[a-z]*)-(\d+)", acc_type)
    if not m:
        raise ValueError(f"invalid TPU accelerator type {acc_type!r}")
    return m.group(1), int(m.group(2))


def validate_topology(topology: str) -> tuple[int, ...]:
    """'2x2x2' -> (2, 2, 2). Reference validates the same way (tpu.py:89)."""
    if not re.fullmatch(r"\d+(x\d+)*", topology):
        raise ValueError(f"invalid TPU topology {topology!r}")
    return tuple(int(x) for x in topology.split("x"))


def get_num_tpu_chips(acc_type: str) -> int:
    gen, count = parse_accelerator_type(acc_type)
    # v2/v3/v5p counts are in TensorCores (2 cores per chip); v4 counts are in
    # chips for the -8 form... The reference normalizes via topology; we treat
    # v2/v3 counts as cores (//2) and everything else as chips.
    if gen in ("v2", "v3"):
        return max(1, count // 2)
    if gen == "v5p":
        return max(1, count // 2)
    return count


def get_chips_per_host(acc_type: str) -> int:
    gen, _ = parse_accelerator_type(acc_type)
    per_host = _GEN_CHIPS_PER_HOST.get(gen, 4)
    chips = get_num_tpu_chips(acc_type)
    return min(per_host, chips)


def get_num_hosts(acc_type: str) -> int:
    chips = get_num_tpu_chips(acc_type)
    return max(1, chips // get_chips_per_host(acc_type))


def get_tpu_slice_name() -> Optional[str]:
    return os.environ.get("TPU_NAME")


def get_tpu_worker_id() -> Optional[int]:
    wid = os.environ.get("TPU_WORKER_ID")
    return int(wid) if wid is not None else None


def get_tpu_pod_type() -> Optional[str]:
    return _accelerator_type()


def get_visible_chips() -> Optional[list[str]]:
    raw = os.environ.get(VISIBLE_CHIPS_ENV)
    if raw is None:
        return None
    return [c for c in raw.split(",") if c != ""]


def set_visible_chips(chip_ids: list[int] | list[str], env: dict | None = None):
    """Restrict a worker process to a subset of the host's chips (reference:
    TPU_VISIBLE_CHIPS isolation, tpu.py:37)."""
    target = env if env is not None else os.environ
    target[VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in chip_ids)
    # JAX honors TPU chip visibility through these:
    target["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,1,{len(chip_ids)}" if chip_ids else ""


def preemption_notice(node_id: str, labels: Optional[dict] = None):
    """Consult the chaos plane for an injected TPU-preemption notice for
    this host (reference: GCE preempts TPU VMs with a short notice; the
    reference's chaos suites simulate it by killing raylets on a timer —
    here it is a seeded, replayable schedule decision). Called once per
    daemon heartbeat; returns the Fault (its ``delay_s`` is the grace
    window) or None. Real-metadata-server detection would slot in here
    alongside the injected path.
    """
    from ray_tpu import chaos

    labels = labels or {}
    return chaos.maybe_inject(
        "tpu.preempt",
        node=node_id[:12],
        worker_id=labels.get(TPU_WORKER_ID_LABEL, ""),
        slice=labels.get(TPU_SLICE_NAME_LABEL, ""),
    )


class TPUAcceleratorManager:
    """Accelerator manager ABC-equivalent (reference: accelerators/accelerator.py)."""

    RESOURCE_NAME = "TPU"

    @staticmethod
    def detect() -> tuple[dict, dict]:
        return detect_tpu_resources()

    @staticmethod
    def slice_head_resource(pod_type: str) -> str:
        # Reference: f"TPU-{pod_type}-head" (tpu.py:224): worker 0 of a slice
        # advertises 1 unit; reserving it gang-locks the slice.
        return f"TPU-{pod_type}-head"


def detect_tpu_resources() -> tuple[dict, dict]:
    """Returns (resources, labels) the node daemon should advertise.

    Env-first (works in tests and GKE); falls back to asking JAX only when a
    TPU runtime is plainly present (JAX_PLATFORMS mentions tpu).
    """
    resources: dict = {}
    labels: dict = {}
    acc_type = _accelerator_type()
    num_chips = 0
    if acc_type:
        try:
            visible = get_visible_chips()
            num_chips = len(visible) if visible is not None else get_chips_per_host(acc_type)
            labels[TPU_POD_TYPE_LABEL] = acc_type
            gen, _ = parse_accelerator_type(acc_type)
            labels[TPU_VERSION_LABEL] = gen
        except ValueError:
            return {}, {}
    elif "tpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        try:
            import jax

            devs = [d for d in jax.devices() if d.platform == "tpu"]
            num_chips = len(devs)
            if devs:
                labels[TPU_VERSION_LABEL] = getattr(devs[0], "device_kind", "tpu")
        except Exception:
            num_chips = 0
    if num_chips <= 0:
        return {}, {}
    resources["TPU"] = float(num_chips)
    topology = os.environ.get("TPU_TOPOLOGY")
    if topology:
        labels[TPU_TOPOLOGY_LABEL] = topology
    slice_name = get_tpu_slice_name()
    if slice_name:
        labels[TPU_SLICE_NAME_LABEL] = slice_name
    worker_id = get_tpu_worker_id()
    if worker_id is not None:
        labels[TPU_WORKER_ID_LABEL] = str(worker_id)
        if worker_id == 0 and acc_type:
            resources[TPUAcceleratorManager.slice_head_resource(acc_type)] = 1.0
    return resources, labels


# ---------------------------------------------------------------------------
# Slice gang reservation (reference: reserve_tpu_slice, tpu.py:224 +
# SlicePlacementGroup, util/tpu.py:181)
# ---------------------------------------------------------------------------


class SliceReservation:
    """A held TPU slice: slice-name label selector + the head-resource PG
    that locks the slice. Release it when the gang is torn down, or the
    slice stays locked against future reservations (incl. our own gang
    restart)."""

    def __init__(self, label_selector: dict, head_pg):
        self.label_selector = label_selector
        self.head_pg = head_pg
        self._released = False

    def release(self):
        if self._released or self.head_pg is None:
            return
        self._released = True
        import ray_tpu as rt

        try:
            rt.remove_placement_group(self.head_pg)
        except Exception:
            pass


def reserve_tpu_slice(accelerator_type: str, topology: Optional[str] = None,
                      num_slices: int = 1, timeout: float = 60.0) -> Optional[SliceReservation]:
    """Reserve whole TPU slice(s) for gang scheduling.

    Places one bundle per slice on the slice-head resource (``TPU-{pod}-head``,
    advertised only by worker 0 of each slice, STRICT_SPREAD so each bundle
    locks a distinct slice), then reads each head node's slice-name label.
    Returns None when no slice-head resource exists in the cluster (CPU test
    topologies without TPU labels).
    """
    import ray_tpu as rt

    if topology is not None:
        dims = validate_topology(topology)
        chips = 1
        for d in dims:
            chips *= d
        expect = get_num_tpu_chips(accelerator_type)
        if chips != expect:
            raise ValueError(
                f"topology {topology} has {chips} chips but {accelerator_type} has {expect}"
            )
    head_res = TPUAcceleratorManager.slice_head_resource(accelerator_type)
    if rt.cluster_resources().get(head_res, 0) < num_slices:
        return None
    pg = rt.placement_group(
        [{head_res: 1.0} for _ in range(num_slices)],
        strategy="STRICT_SPREAD" if num_slices > 1 else "STRICT_PACK",
        name=f"slice-{accelerator_type}",
    )
    if not pg.ready(timeout=timeout):
        rt.remove_placement_group(pg)
        raise TimeoutError(
            f"no {num_slices} free {accelerator_type} slice(s) (resource {head_res})"
        )
    node_labels = {n["NodeID"]: n.get("labels", {}) for n in rt.nodes()}
    names = [
        node_labels.get(nid, {}).get(TPU_SLICE_NAME_LABEL)
        for nid in pg.bundle_nodes()
    ]
    names = [n for n in names if n]
    if not names:
        return SliceReservation({}, pg)
    # Selector syntax per the controller's matcher: "v" or "in(a,b)".
    selector = {
        TPU_SLICE_NAME_LABEL: names[0] if len(names) == 1 else f"in({','.join(names)})"
    }
    return SliceReservation(selector, pg)


class SlicePlacementGroup:
    """Multi-host slice gang: one bundle per TPU host, STRICT_SPREAD and
    label-pinned to the reserved slice(s) (reference: util/tpu.py:181)."""

    def __init__(self, accelerator_type: str, topology: Optional[str] = None,
                 num_slices: int = 1):
        import ray_tpu as rt

        self.accelerator_type = accelerator_type
        self.num_hosts = get_num_hosts(accelerator_type) * num_slices
        chips = get_chips_per_host(accelerator_type)
        self.reservation = reserve_tpu_slice(
            accelerator_type, topology, num_slices=num_slices
        )
        selector = self.reservation.label_selector if self.reservation else {}
        self.pg = rt.placement_group(
            [{"TPU": float(chips)} for _ in range(self.num_hosts)],
            strategy="STRICT_SPREAD" if self.num_hosts > 1 else "PACK",
            name=f"slice-pg-{accelerator_type}",
            label_selector=selector,
        )

    @property
    def label_selector(self) -> dict:
        return self.reservation.label_selector if self.reservation else {}

    def ready(self, timeout: float = 60.0) -> bool:
        return self.pg.ready(timeout=timeout)

    def release(self):
        import ray_tpu as rt

        try:
            rt.remove_placement_group(self.pg)
        except Exception:
            pass
        if self.reservation:
            self.reservation.release()
