from ray_tpu.accel.tpu import (
    TPUAcceleratorManager,
    detect_tpu_resources,
    get_chips_per_host,
    get_num_tpu_chips,
    get_tpu_pod_type,
    get_tpu_slice_name,
    get_tpu_worker_id,
)

__all__ = [
    "TPUAcceleratorManager",
    "detect_tpu_resources",
    "get_chips_per_host",
    "get_num_tpu_chips",
    "get_tpu_pod_type",
    "get_tpu_slice_name",
    "get_tpu_worker_id",
]
